"""Out/LSE correction family vs first-principles softmax (reference
functional/utils.py correct_attn_* + the _with_sink variants)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from magiattention_tpu.ops import (
    correct_attn_lse,
    correct_attn_lse_with_sink,
    correct_attn_out,
    correct_attn_out_lse,
    correct_attn_out_lse_with_sink,
    correct_attn_out_with_sink,
)


def _partials(tq=16, h=2, d=8, tk=24, split=10, seed=0):
    """One attention computed whole and as two disjoint-KV partials."""
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((tq, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((tk, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((tk, h, d)), jnp.float32)

    def attend(k_, v_):
        s = jnp.einsum("qhd,khd->qhk", q, k_)  # scale-free: math identity
        lse = jax.nn.logsumexp(s, axis=-1)
        out = jnp.einsum("qhk,khd->qhd", jax.nn.softmax(s, axis=-1), v_)
        return out, lse

    full = attend(k, v)
    p1 = attend(k[:split], v[:split])
    p2 = attend(k[split:], v[split:])
    return full, p1, p2


def test_out_lse_merge_matches_whole():
    (out_f, lse_f), (o1, l1), (o2, l2) = _partials()
    out, lse = correct_attn_out_lse(o1, l1, o2, l2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_f), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_f), rtol=1e-5,
                               atol=1e-6)
    # the split spellings agree with the fused one
    lse2 = correct_attn_lse(l1, l2)
    out2 = correct_attn_out(o1, l1, o2, l2, lse2)
    np.testing.assert_allclose(np.asarray(lse2), np.asarray(lse), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out), rtol=1e-6)


def test_uncovered_rows_stay_neutral():
    (_, _), (o1, l1), _ = _partials()
    neg = jnp.full_like(l1, -jnp.inf)
    zero = jnp.zeros_like(o1)
    out, lse = correct_attn_out_lse(o1, l1, zero, neg)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(l1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out), np.asarray(o1), rtol=1e-6)
    out0, lse0 = correct_attn_out_lse(zero, neg, zero, neg)
    assert np.all(np.isneginf(np.asarray(lse0)))
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(zero))


@pytest.mark.parametrize("layout,s_shape", [("sh", (3,)), ("ssh", (16, 3))])
def test_sink_fold_matches_direct_softmax(layout, s_shape):
    """Folding sink logits post-hoc == computing softmax with the sink
    columns in the denominator from the start."""
    (out_f, lse_f), _, _ = _partials()
    h = lse_f.shape[1]
    rng = np.random.default_rng(1)
    sink = jnp.asarray(rng.standard_normal(s_shape + (h,)), jnp.float32)

    out_s, lse_s = correct_attn_out_lse_with_sink(out_f, lse_f, sink, layout)
    # direct: denominator gains sum(exp(sink)) per (row, head)
    s_lse = (
        jax.nn.logsumexp(sink, axis=0)[None, :]
        if layout == "sh"
        else jax.nn.logsumexp(sink, axis=1)
    )
    lse_direct = jnp.logaddexp(lse_f, jnp.broadcast_to(s_lse, lse_f.shape))
    out_direct = out_f * jnp.exp(lse_f - lse_direct)[..., None]
    np.testing.assert_allclose(np.asarray(lse_s), np.asarray(lse_direct),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_direct),
                               rtol=1e-6)
    # split spellings agree
    np.testing.assert_allclose(
        np.asarray(correct_attn_lse_with_sink(lse_f, sink, layout)),
        np.asarray(lse_s), rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(correct_attn_out_with_sink(out_f, lse_f, sink, layout)),
        np.asarray(out_s), rtol=1e-6,
    )


def test_shd_fold_matches_appended_token_softmax():
    """shd (zero-logit value-carrying sinks, ops/correction.py:_sink_lse)
    == dense attention with S extra KV tokens whose logits are 0 and
    whose values are sink[s, h, :]."""
    (out_f, lse_f), _, _ = _partials()
    tq, h = lse_f.shape
    d = out_f.shape[-1]
    S = 3
    rng = np.random.default_rng(2)
    sink = jnp.asarray(rng.standard_normal((S, h, d)), jnp.float32)

    out_s, lse_s = correct_attn_out_lse_with_sink(out_f, lse_f, sink, "shd")

    # oracle: probs = softmax([scores, 0 x S]); out = p_kv @ V + p_sink @ sink
    lse_direct = jnp.logaddexp(lse_f, jnp.log(float(S)))
    w_kv = jnp.exp(lse_f - lse_direct)  # total prob mass on real KV
    p_one_sink = jnp.exp(-lse_direct)  # each sink token's prob
    out_direct = (
        out_f * w_kv[..., None]
        + p_one_sink[..., None] * sink.sum(axis=0)[None]
    )
    np.testing.assert_allclose(np.asarray(lse_s), np.asarray(lse_direct),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_direct),
                               rtol=1e-6, atol=1e-7)
    # split spellings agree
    np.testing.assert_allclose(
        np.asarray(correct_attn_lse_with_sink(lse_f, sink, "shd")),
        np.asarray(lse_s), rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(correct_attn_out_with_sink(out_f, lse_f, sink, "shd")),
        np.asarray(out_s), rtol=1e-6,
    )


def test_shd_zero_values_is_softmax_off_by_S():
    """All-zero shd values only enlarge the denominator (softmax1-style)."""
    (out_f, lse_f), _, _ = _partials()
    h, d = lse_f.shape[1], out_f.shape[-1]
    sink = jnp.zeros((1, h, d), jnp.float32)
    out_s, lse_s = correct_attn_out_lse_with_sink(out_f, lse_f, sink, "shd")
    np.testing.assert_allclose(
        np.asarray(lse_s), np.asarray(jnp.logaddexp(lse_f, 0.0)), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(out_s),
        np.asarray(out_f * jnp.exp(lse_f - lse_s)[..., None]),
        rtol=1e-6,
    )


def test_shd_uncovered_row_averages_sinks():
    """A row with lse=-inf attends only to the sinks -> mean sink value."""
    h, d, S = 2, 8, 4
    rng = np.random.default_rng(3)
    sink = jnp.asarray(rng.standard_normal((S, h, d)), jnp.float32)
    out = jnp.zeros((5, h, d), jnp.float32)
    lse = jnp.full((5, h), -jnp.inf, jnp.float32)
    out_s, lse_s = correct_attn_out_lse_with_sink(out, lse, sink, "shd")
    np.testing.assert_allclose(
        np.asarray(lse_s), np.full((5, h), np.log(S), np.float32), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(out_s),
        np.broadcast_to(np.asarray(sink.mean(axis=0)), (5, h, d)),
        rtol=1e-5, atol=1e-6,
    )


def test_shd_grads_flow_to_sink_values():
    """The correction post-pass is autodiff-transparent: d(loss)/d(sink)
    matches the appended-token oracle's gradient."""
    (out_f, lse_f), _, _ = _partials(tq=8, h=2, d=4)
    S, h, d = 2, 2, 4
    rng = np.random.default_rng(4)
    sink0 = jnp.asarray(rng.standard_normal((S, h, d)), jnp.float32)

    def loss_impl(s):
        return correct_attn_out_lse_with_sink(out_f, lse_f, s, "shd")[0].sum()

    def loss_oracle(s):
        lse_tot = jnp.logaddexp(lse_f, jnp.log(float(S)))
        o = out_f * jnp.exp(lse_f - lse_tot)[..., None] + jnp.exp(-lse_tot)[
            ..., None
        ] * s.sum(axis=0)[None]
        return o.sum()

    g_impl = jax.grad(loss_impl)(sink0)
    g_oracle = jax.grad(loss_oracle)(sink0)
    np.testing.assert_allclose(np.asarray(g_impl), np.asarray(g_oracle),
                               rtol=1e-5, atol=1e-6)
    assert float(jnp.abs(g_impl).sum()) > 0


def test_unknown_layout_rejected():
    with pytest.raises(ValueError, match="sink_layout"):
        correct_attn_lse_with_sink(
            jnp.zeros((4, 2)), jnp.zeros((1, 2, 8)), "hsd"
        )
