"""Entry-table budget guards for the headline bench shapes."""


def test_128k_causal_auto_config_fits_budget():
    """The north-star 128k-causal bench row (BASELINE.md config 3): the
    auto-selected fwd AND bwd entry tables must fit the SMEM
    scalar-prefetch budget, so the on-chip sweep cannot fail on table
    size when the chip window opens."""
    from magiattention_tpu.ops.block_meta import build_block_meta
    from magiattention_tpu.ops.flex_attn import (
        _MAX_SMEM_ENTRIES,
        auto_block_config,
    )

    total = 131072
    qr, kr, ts = [(0, total)], [(0, total)], [1]
    bq, bk, _hb = auto_block_config(qr, kr, 8, 8)
    meta = build_block_meta(qr, kr, ts, total, total, block_q=bq, block_k=bk)
    assert meta.num_fwd_entries <= _MAX_SMEM_ENTRIES, (
        meta.num_fwd_entries, bq, bk,
    )
    assert meta.num_bwd_entries <= _MAX_SMEM_ENTRIES, (
        meta.num_bwd_entries, bq, bk,
    )
