"""Block-sparse attention vs dense oracle (reference test_block_sparse_attn)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from magiattention_tpu.ops import block_sparse_attn_func
from magiattention_tpu.testing import assert_close, ref_attn


def _dense_mask_from_blocks(bm, total_q, total_k, bq, bk, causal):
    m = np.zeros((total_q, total_k), bool)
    for i in range(bm.shape[0]):
        for j in range(bm.shape[1]):
            if bm[i, j]:
                m[i * bq : (i + 1) * bq, j * bk : (j + 1) * bk] = True
    if causal:
        qi = np.arange(total_q)[:, None]
        ki = np.arange(total_k)[None, :]
        m &= ki <= qi + (total_k - total_q)
    return m


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_block_sparse_matches_oracle(causal, seed):
    total, bq, bk = 512, 64, 64
    hq, hk, d = 4, 2, 64
    rng = np.random.default_rng(seed)
    bm = rng.random((total // bq, total // bk)) < 0.4
    bm[np.arange(total // bq), np.arange(total // bk)] = True  # keep diagonal
    q = jnp.asarray(rng.standard_normal((total, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((total, hk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((total, hk, d)), jnp.float32)
    out, lse = block_sparse_attn_func(
        q, k, v, bm, causal=causal, block_q=bq, block_k=bk
    )
    mask = _dense_mask_from_blocks(bm, total, total, bq, bk, causal)
    ref_out, ref_lse, _ = ref_attn(q, k, v, mask)
    assert_close(out, ref_out, atol=2e-5, rtol=2e-5, msg=f"bs causal={causal}")
    finite = ~np.isneginf(np.asarray(ref_lse))
    assert_close(
        np.asarray(lse)[finite], np.asarray(ref_lse)[finite],
        atol=2e-5, rtol=2e-5,
    )

    # bwd through the sparse plan
    do = jnp.asarray(rng.standard_normal((total, hq, d)), jnp.float32)
    g = jax.grad(
        lambda k: (
            block_sparse_attn_func(q, k, v, bm, causal=causal, block_q=bq, block_k=bk)[0]
            * do
        ).sum()
    )(k)
    gr = jax.grad(lambda k: (ref_attn(q, k, v, mask)[0] * do).sum())(k)
    assert_close(g, gr, atol=1e-4, rtol=1e-4, msg=f"bs dk causal={causal}")


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("tq,tk", [(256, 512), (512, 256)])
def test_block_sparse_rect_cross(tq, tk, causal):
    """Rectangular (cross-attn) block mask, incl. the off!=0 causal
    diagonal clipping in both orientations."""
    bq = bk = 64
    rng = np.random.default_rng(5)
    bm = rng.random((tq // bq, tk // bk)) < 0.5
    bm[:, :] |= np.eye(tq // bq, tk // bk, k=(tk - tq) // bk, dtype=bool)
    q = jnp.asarray(rng.standard_normal((tq, 2, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((tk, 2, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((tk, 2, 32)), jnp.float32)
    out, lse = block_sparse_attn_func(
        q, k, v, bm, causal=causal, block_q=bq, block_k=bk
    )
    mask = _dense_mask_from_blocks(bm, tq, tk, bq, bk, causal)
    ref_out, ref_lse, _ = ref_attn(q, k, v, mask)
    assert_close(out, ref_out, atol=2e-5, rtol=2e-5, msg=f"rect {tq}x{tk} c={causal}")
    finite = ~np.isneginf(np.asarray(ref_lse))
    np.testing.assert_array_equal(
        np.isneginf(np.asarray(lse)), ~finite
    )
    assert_close(
        np.asarray(lse)[finite], np.asarray(ref_lse)[finite],
        atol=2e-5, rtol=2e-5,
    )
