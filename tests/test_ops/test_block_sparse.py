"""Block-sparse attention vs dense oracle (reference test_block_sparse_attn)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from magiattention_tpu.ops import block_sparse_attn_func
from magiattention_tpu.testing import assert_close, ref_attn


def _dense_mask_from_blocks(bm, total_q, total_k, bq, bk, causal):
    m = np.zeros((total_q, total_k), bool)
    for i in range(bm.shape[0]):
        for j in range(bm.shape[1]):
            if bm[i, j]:
                m[i * bq : (i + 1) * bq, j * bk : (j + 1) * bk] = True
    if causal:
        qi = np.arange(total_q)[:, None]
        ki = np.arange(total_k)[None, :]
        m &= ki <= qi + (total_k - total_q)
    return m


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_block_sparse_matches_oracle(causal, seed):
    total, bq, bk = 512, 64, 64
    hq, hk, d = 4, 2, 64
    rng = np.random.default_rng(seed)
    bm = rng.random((total // bq, total // bk)) < 0.4
    bm[np.arange(total // bq), np.arange(total // bk)] = True  # keep diagonal
    q = jnp.asarray(rng.standard_normal((total, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((total, hk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((total, hk, d)), jnp.float32)
    out, lse = block_sparse_attn_func(
        q, k, v, bm, causal=causal, block_q=bq, block_k=bk
    )
    mask = _dense_mask_from_blocks(bm, total, total, bq, bk, causal)
    ref_out, ref_lse, _ = ref_attn(q, k, v, mask)
    assert_close(out, ref_out, atol=2e-5, rtol=2e-5, msg=f"bs causal={causal}")
    finite = ~np.isneginf(np.asarray(ref_lse))
    assert_close(
        np.asarray(lse)[finite], np.asarray(ref_lse)[finite],
        atol=2e-5, rtol=2e-5,
    )

    # bwd through the sparse plan
    do = jnp.asarray(rng.standard_normal((total, hq, d)), jnp.float32)
    g = jax.grad(
        lambda k: (
            block_sparse_attn_func(q, k, v, bm, causal=causal, block_q=bq, block_k=bk)[0]
            * do
        ).sum()
    )(k)
    gr = jax.grad(lambda k: (ref_attn(q, k, v, mask)[0] * do).sum())(k)
    assert_close(g, gr, atol=1e-4, rtol=1e-4, msg=f"bs dk causal={causal}")


def test_block_sparse_causal_area_unequal_blocks():
    """Advisor regression: with block_k < block_q, diagonal-crossing tiles
    must not under-attend (128x128 all-True causal area is 8256)."""
    from magiattention_tpu.ops.block_sparse import (
        build_block_meta_from_block_mask,
    )

    total = 128
    for bq, bk in [(128, 64), (64, 128), (128, 32), (32, 128), (64, 64)]:
        bm = np.ones((-(-total // bq), -(-total // bk)), bool)
        meta = build_block_meta_from_block_mask(
            bm, total, total, block_q=bq, block_k=bk, causal=True
        )
        expect = total * (total + 1) // 2
        assert meta.total_area == expect, (bq, bk, meta.total_area, expect)


@pytest.mark.parametrize("bq,bk", [(128, 64), (64, 128), (128, 32)])
def test_block_sparse_causal_unequal_blocks_oracle(bq, bk):
    """Advisor regression: causal block-sparse with block_q != block_k vs
    the dense oracle (crossing tiles with k1 < q1 + off)."""
    total = 256
    hq, hk, d = 2, 2, 32
    rng = np.random.default_rng(7)
    bm = rng.random((-(-total // bq), -(-total // bk))) < 0.6
    bm[:, 0] = True  # keep every row attending something below the diagonal
    q = jnp.asarray(rng.standard_normal((total, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((total, hk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((total, hk, d)), jnp.float32)
    out, lse = block_sparse_attn_func(
        q, k, v, bm, causal=True, block_q=bq, block_k=bk
    )
    mask = _dense_mask_from_blocks(bm, total, total, bq, bk, True)
    ref_out, ref_lse, _ = ref_attn(q, k, v, mask)
    assert_close(out, ref_out, atol=2e-5, rtol=2e-5, msg=f"bq={bq} bk={bk}")
    finite = ~np.isneginf(np.asarray(ref_lse))
    assert_close(
        np.asarray(lse)[finite], np.asarray(ref_lse)[finite],
        atol=2e-5, rtol=2e-5,
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("tq,tk", [(256, 512), (512, 256)])
def test_block_sparse_rect_cross(tq, tk, causal):
    """Rectangular (cross-attn) block mask, incl. the off!=0 causal
    diagonal clipping in both orientations."""
    bq = bk = 64
    rng = np.random.default_rng(5)
    bm = rng.random((tq // bq, tk // bk)) < 0.5
    bm[:, :] |= np.eye(tq // bq, tk // bk, k=(tk - tq) // bk, dtype=bool)
    q = jnp.asarray(rng.standard_normal((tq, 2, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((tk, 2, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((tk, 2, 32)), jnp.float32)
    out, lse = block_sparse_attn_func(
        q, k, v, bm, causal=causal, block_q=bq, block_k=bk
    )
    mask = _dense_mask_from_blocks(bm, tq, tk, bq, bk, causal)
    ref_out, ref_lse, _ = ref_attn(q, k, v, mask)
    assert_close(out, ref_out, atol=2e-5, rtol=2e-5, msg=f"rect {tq}x{tk} c={causal}")
    finite = ~np.isneginf(np.asarray(ref_lse))
    np.testing.assert_array_equal(
        np.isneginf(np.asarray(lse)), ~finite
    )
    assert_close(
        np.asarray(lse)[finite], np.asarray(ref_lse)[finite],
        atol=2e-5, rtol=2e-5,
    )


def test_block_mask_shape_mismatch_raises_typed_error():
    """ISSUE 15 hardening: a block mask built for the wrong blocking (or
    transposed [k, q]) raises a ValueError carrying the full shape
    context — not a bare assert that ``python -O`` would strip."""
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((256, 2, 32)), jnp.float32)
    bad = np.ones((3, 4), bool)  # 256 tokens at 64x64 needs (4, 4)
    with pytest.raises(ValueError) as ei:
        block_sparse_attn_func(q, q, q, bad, block_q=64, block_k=64)
    msg = str(ei.value)
    assert "(3, 4)" in msg and "(4, 4)" in msg
    assert "(256, 256)" in msg and "(64, 64)" in msg
    # transposed layout of a rectangular problem is called out too
    k = jnp.asarray(rng.standard_normal((512, 2, 32)), jnp.float32)
    with pytest.raises(ValueError, match="num_q_blocks, num_k_blocks"):
        block_sparse_attn_func(
            q, k, k, np.ones((8, 4), bool), block_q=64, block_k=64
        )
