"""Native (C++) planner vs Python fallback parity.

Model: reference tests/test_common/test_protocol_conformance.py — the C++
backend must produce identical planning results to the Python oracle.
"""

import numpy as np
import pytest

from magiattention_tpu.csrc import (
    emit_entries_native,
    get_lib,
    slice_area_runs_native,
)
from magiattention_tpu.ops.block_meta import (
    Run,
    _emit_entries,
    _slice_k_span,
    _sub_area,
)

pytestmark = pytest.mark.skipif(
    get_lib() is None, reason="native backend unavailable (no g++?)"
)


def _random_case(rng, n_slices=6, n_q_runs=3, n_k_runs=4, span=512):
    slices = []
    for _ in range(n_slices):
        qs = int(rng.integers(0, span - 1))
        qe = int(rng.integers(qs + 1, span + 1))
        ks = int(rng.integers(0, span - 1))
        ke = int(rng.integers(ks + 1, span + 1))
        slices.append((qs, qe, ks, ke, int(rng.integers(0, 4))))
    slices = np.asarray(slices, dtype=np.int64)

    def runs(n):
        out, local = [], 0
        for _ in range(n):
            length = int(rng.integers(16, 128))
            gstart = int(rng.integers(0, span))
            out.append(Run(local, gstart, length))
            local += length
        return out

    return slices, runs(n_q_runs), runs(n_k_runs)


@pytest.mark.parametrize("seed", range(8))
def test_emit_entries_parity(seed):
    rng = np.random.default_rng(seed)
    slices, q_runs, k_runs = _random_case(rng)
    bq = bk = int(rng.choice([32, 64, 128]))
    py = _emit_entries(slices, q_runs, k_runs, bq, bk)
    py_arr = (
        np.asarray(py, dtype=np.int64) if py else np.empty((0, 9), np.int64)
    )
    q_arr = np.asarray(
        [(r.local_start, r.global_start, r.length) for r in q_runs], np.int64
    )
    k_arr = np.asarray(
        [(r.local_start, r.global_start, r.length) for r in k_runs], np.int64
    )
    cpp = emit_entries_native(slices, q_arr, k_arr, bq, bk)
    np.testing.assert_array_equal(cpp, py_arr)


@pytest.mark.parametrize("seed", range(8))
def test_area_parity(seed):
    rng = np.random.default_rng(100 + seed)
    slices, q_runs, k_runs = _random_case(rng)
    py_area = 0
    for sid in range(slices.shape[0]):
        qs, qe, ks, ke, mt = (int(x) for x in slices[sid])
        for qr in q_runs:
            a, b = max(qs, qr.global_start), min(qe, qr.global_end)
            if a >= b:
                continue
            k_lo, k_hi = _slice_k_span(a, b, ks, ke, qs, qe, mt)
            for kr in k_runs:
                c, d = max(k_lo, kr.global_start), min(k_hi, kr.global_end)
                if c >= d:
                    continue
                py_area += _sub_area(a, b, c, d, qs, qe, ks, ke, mt)
    q_arr = np.asarray(
        [(r.local_start, r.global_start, r.length) for r in q_runs], np.int64
    )
    k_arr = np.asarray(
        [(r.local_start, r.global_start, r.length) for r in k_runs], np.int64
    )
    assert slice_area_runs_native(slices, q_arr, k_arr) == py_area