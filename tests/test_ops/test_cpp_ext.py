"""Native (C++) planner vs Python fallback parity.

Model: reference tests/test_common/test_protocol_conformance.py — the C++
backend must produce identical planning results to the Python oracle.
"""

import numpy as np
import pytest

from magiattention_tpu.csrc import (
    emit_entries_native,
    get_lib,
    slice_area_runs_native,
)
from magiattention_tpu.ops.block_meta import (
    Run,
    _emit_entries,
    _slice_k_span,
    _sub_area,
)

pytestmark = pytest.mark.skipif(
    get_lib() is None, reason="native backend unavailable (no g++?)"
)


def _random_case(rng, n_slices=6, n_q_runs=3, n_k_runs=4, span=512):
    slices = []
    for _ in range(n_slices):
        qs = int(rng.integers(0, span - 1))
        qe = int(rng.integers(qs + 1, span + 1))
        ks = int(rng.integers(0, span - 1))
        ke = int(rng.integers(ks + 1, span + 1))
        slices.append((qs, qe, ks, ke, int(rng.integers(0, 4))))
    slices = np.asarray(slices, dtype=np.int64)

    def runs(n):
        out, local = [], 0
        for _ in range(n):
            length = int(rng.integers(16, 128))
            gstart = int(rng.integers(0, span))
            out.append(Run(local, gstart, length))
            local += length
        return out

    return slices, runs(n_q_runs), runs(n_k_runs)


@pytest.mark.parametrize("seed", range(8))
def test_emit_entries_parity(seed):
    rng = np.random.default_rng(seed)
    slices, q_runs, k_runs = _random_case(rng)
    bq = bk = int(rng.choice([32, 64, 128]))
    py = _emit_entries(slices, q_runs, k_runs, bq, bk)
    py_arr = (
        np.asarray(py, dtype=np.int64) if py else np.empty((0, 9), np.int64)
    )
    q_arr = np.asarray(
        [(r.local_start, r.global_start, r.length) for r in q_runs], np.int64
    )
    k_arr = np.asarray(
        [(r.local_start, r.global_start, r.length) for r in k_runs], np.int64
    )
    cpp = emit_entries_native(slices, q_arr, k_arr, bq, bk)
    np.testing.assert_array_equal(cpp, py_arr)


@pytest.mark.parametrize("seed", range(8))
def test_area_parity(seed):
    rng = np.random.default_rng(100 + seed)
    slices, q_runs, k_runs = _random_case(rng)
    py_area = 0
    for sid in range(slices.shape[0]):
        qs, qe, ks, ke, mt = (int(x) for x in slices[sid])
        for qr in q_runs:
            a, b = max(qs, qr.global_start), min(qe, qr.global_end)
            if a >= b:
                continue
            k_lo, k_hi = _slice_k_span(a, b, ks, ke, qs, qe, mt)
            for kr in k_runs:
                c, d = max(k_lo, kr.global_start), min(k_hi, kr.global_end)
                if c >= d:
                    continue
                py_area += _sub_area(a, b, c, d, qs, qe, ks, ke, mt)
    q_arr = np.asarray(
        [(r.local_start, r.global_start, r.length) for r in q_runs], np.int64
    )
    k_arr = np.asarray(
        [(r.local_start, r.global_start, r.length) for r in k_runs], np.int64
    )
    assert slice_area_runs_native(slices, q_arr, k_arr) == py_area

def _random_rects(rng, n=8, span=512):
    from magiattention_tpu.common import AttnMaskType, AttnRange
    from magiattention_tpu.common.rectangle import (
        AttnRectangle,
        AttnRectangles,
    )

    rects = AttnRectangles()
    for _ in range(n):
        qs = int(rng.integers(0, span - 1))
        qe = int(rng.integers(qs + 1, span + 1))
        ks = int(rng.integers(0, span - 1))
        ke = int(rng.integers(ks + 1, span + 1))
        r = AttnRectangle(
            AttnRange(qs, qe),
            AttnRange(ks, ke),
            AttnMaskType(int(rng.integers(0, 4))),
        )
        if r.area > 0:
            rects.append(r)
    return rects


@pytest.mark.parametrize("seed", range(8))
def test_area_left_parity(seed):
    """Native magi_area_left == Python area_left_of_q / area_left_of_k."""
    from magiattention_tpu.csrc import area_left_native

    rng = np.random.default_rng(300 + seed)
    rects = _random_rects(rng)
    arr = rects.to_array()
    for pos in [0, 7, 100, 255, 256, 400, 512, 600]:
        assert area_left_native(arr, True, pos) == rects.area_left_of_q(pos)
        assert area_left_native(arr, False, pos) == rects.area_left_of_k(pos)


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("axis_q", [True, False])
def test_cut_pos_parity(seed, axis_q):
    """Native binary search returns the identical cut position to the
    Python probe loop for every fraction the KD solver uses."""
    from magiattention_tpu.csrc import cut_pos_native

    rng = np.random.default_rng(400 + seed)
    rects = _random_rects(rng)
    if rects.area == 0:
        pytest.skip("degenerate")
    arr = rects.to_array()

    def python_cut_pos(frac):
        total = rects.area
        if axis_q:
            lo = min(r.q_range.start for r in rects)
            hi = max(r.q_range.end for r in rects)
            area_left = rects.area_left_of_q
        else:
            lo = min(r.k_range.start for r in rects)
            hi = max(r.k_range.end for r in rects)
            area_left = rects.area_left_of_k
        target = frac * total
        best_pos, best_err = lo, abs(area_left(lo) - target)
        while lo < hi:
            mid = (lo + hi) // 2
            a = area_left(mid)
            err = abs(a - target)
            if err < best_err:
                best_pos, best_err = mid, err
            if a < target:
                lo = mid + 1
            else:
                hi = mid
        if abs(area_left(lo) - target) < best_err:
            best_pos = lo
        return best_pos

    for frac in [0.5, 0.25, 1 / 3, 0.125, 2 / 3]:
        assert cut_pos_native(arr, frac, axis_q) == python_cut_pos(frac), frac


def test_dynamic_solver_native_matches_python(monkeypatch):
    """DynamicAttnSolver with the native probe == pure-Python solve."""
    from magiattention_tpu.meta.solver.dynamic_attn_solver import (
        DynamicAttnSolver,
    )

    rng = np.random.default_rng(42)
    rects = _random_rects(rng, n=12, span=1024)
    solver = DynamicAttnSolver()
    native = solver.solve(rects, cp_size=8)

    import magiattention_tpu.csrc as csrc

    monkeypatch.setattr(csrc, "cut_pos_native", lambda *a, **k: None)
    pure = solver.solve(rects, cp_size=8)
    assert native.areas == pure.areas
    assert [len(r) for r in native.rank_rects] == [
        len(r) for r in pure.rank_rects
    ]


def test_stale_so_rebuilds(tmp_path, monkeypatch):
    """A .so missing newer symbols (mtime-equal after cp -r) must trigger
    one rebuild instead of crashing get_lib with AttributeError."""
    import shutil
    import subprocess

    import magiattention_tpu.csrc as csrc

    src = tmp_path / "entry_table.cpp"
    so = tmp_path / "libmagi_ext.so"
    shutil.copy(csrc._SRC, src)
    # stale library: compiled from an empty TU -> none of our symbols
    stub = tmp_path / "stub.cpp"
    stub.write_text("extern \"C\" int magi_nothing() { return 0; }\n")
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", str(stub), "-o", str(so)],
        check=True,
        capture_output=True,
    )
    # make the .so look newer than the source (skips the mtime rebuild)
    times = (src.stat().st_mtime + 100, src.stat().st_mtime + 100)
    import os as _os

    _os.utime(so, times)

    monkeypatch.setattr(csrc, "_SRC", str(src))
    monkeypatch.setattr(csrc, "_SO", str(so))
    monkeypatch.setattr(csrc, "_LIB", None)
    monkeypatch.setattr(csrc, "_TRIED", False)
    lib = csrc.get_lib()
    assert lib is not None  # rebuilt from source and bound
    assert lib.magi_cut_pos is not None
