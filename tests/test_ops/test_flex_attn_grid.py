"""Feature-combination grid for the flex kernel.

The reference's kernel matrix (tests/test_attn/test_flex_flash_attn.py,
~2k LoC) sweeps features *in combination* — sink x softcap x GQA x
head_dim x mask type — not just one at a time. This file adds that axis
product on top of the per-feature tests in test_flex_attn.py, plus
bitwise-determinism checks (the TPU design's replacement for the
reference's MAGI_ATTENTION_DETERMINISTIC_MODE: no atomics anywhere, so
identical calls must be bit-identical, flash.h:103-106 analogue).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from magiattention_tpu.common import AttnMaskType
from magiattention_tpu.ops import flex_flash_attn_func
from magiattention_tpu.testing import assert_close, ref_attn_from_ranges

F = AttnMaskType.FULL
C = AttnMaskType.CAUSAL
I = AttnMaskType.INVCAUSAL
B = AttnMaskType.BICAUSAL

# one mask that exercises all four types + q-overlap in a single plan
_MIXED = (
    256,
    256,
    [(0, 64), (64, 128), (128, 192), (192, 256), (32, 96)],
    [(0, 128), (0, 64), (64, 200), (100, 256), (128, 256)],
    [C, F, I, B, F],
)


def _rand(tq, tk, hq, hk, d, seed, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((tq, hq, d)), dtype)
    k = jnp.asarray(rng.standard_normal((tk, hk, d)), dtype)
    v = jnp.asarray(rng.standard_normal((tk, hk, d)), dtype)
    return q, k, v


@pytest.mark.parametrize("d", [64, 128])
@pytest.mark.parametrize("hq,hk", [(4, 4), (4, 2), (4, 1)])
@pytest.mark.parametrize("softcap", [0.0, 15.0])
@pytest.mark.parametrize("with_sink", [False, True])
def test_feature_product_fwd(d, hq, hk, softcap, with_sink):
    """sink x softcap x GQA (incl. MQA hk=1) x head_dim on the mixed-type
    q-overlap mask, fwd out + lse vs oracle."""
    tq, tk, qr, kr, ts = _MIXED
    q, k, v = _rand(tq, tk, hq, hk, d, seed=d + hk)
    sink = (
        jnp.asarray(np.random.default_rng(7).standard_normal(hq), jnp.float32)
        if with_sink
        else None
    )
    out, lse = flex_flash_attn_func(
        q, k, v, qr, kr, ts, block_q=64, block_k=64,
        softcap=softcap, sink=sink,
    )[:2]
    ref_out, ref_lse, _ = ref_attn_from_ranges(
        q, k, v, qr, kr, ts, softcap=softcap, sink=sink
    )
    tag = f"d={d} h={hq}:{hk} cap={softcap} sink={with_sink}"
    assert_close(out, ref_out, atol=3e-5, rtol=3e-5, msg=f"{tag} out")
    mask = ~np.isneginf(np.asarray(ref_lse))
    assert_close(
        np.asarray(lse)[mask], np.asarray(ref_lse)[mask],
        atol=3e-5, rtol=3e-5, msg=f"{tag} lse",
    )


@pytest.mark.parametrize("hq,hk", [(4, 2), (4, 1)])
def test_feature_product_bwd_sink_softcap(hq, hk):
    """Gradients with sink AND softcap enabled together (the combination
    the per-feature tests never exercise), GQA + MQA."""
    tq, tk, qr, kr, ts = _MIXED
    d = 64
    q, k, v = _rand(tq, tk, hq, hk, d, seed=3)
    rng = np.random.default_rng(5)
    sink0 = jnp.asarray(rng.standard_normal(hq), jnp.float32)
    do = jnp.asarray(rng.standard_normal((tq, hq, d)), jnp.float32)

    def f(q, k, v, s):
        out = flex_flash_attn_func(
            q, k, v, qr, kr, ts, block_q=64, block_k=64,
            softcap=10.0, sink=s,
        )[0]
        return (out * do).sum()

    def f_ref(q, k, v, s):
        out, _, _ = ref_attn_from_ranges(
            q, k, v, qr, kr, ts, softcap=10.0, sink=s
        )
        return (out * do).sum()

    g = jax.grad(f, argnums=(0, 1, 2, 3))(q, k, v, sink0)
    gr = jax.grad(f_ref, argnums=(0, 1, 2, 3))(q, k, v, sink0)
    for name, a, b in zip(("dq", "dk", "dv", "dsink"), g, gr):
        assert_close(a, b, atol=1e-4, rtol=1e-4, msg=f"{hq}:{hk} {name}")


def test_kernel_bitwise_deterministic():
    """Two identical kernel calls are bit-identical (out, lse, and grads).

    The reference needs MAGI_ATTENTION_DETERMINISTIC_MODE to replace
    dkv atomics with ordered range-locks; this design has no atomics, so
    determinism is unconditional — verify it stays that way."""
    tq, tk, qr, kr, ts = _MIXED
    hq, hk, d = 4, 2, 64
    q, k, v = _rand(tq, tk, hq, hk, d, seed=11)
    do = jnp.asarray(
        np.random.default_rng(13).standard_normal((tq, hq, d)), jnp.float32
    )

    fwd = jax.jit(
        lambda q, k, v: flex_flash_attn_func(
            q, k, v, qr, kr, ts, block_q=64, block_k=64
        )[:2]
    )
    out1, lse1 = fwd(q, k, v)
    out2, lse2 = fwd(q, k, v)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    np.testing.assert_array_equal(np.asarray(lse1), np.asarray(lse2))

    grad = jax.jit(
        jax.grad(
            lambda q, k, v: (
                flex_flash_attn_func(
                    q, k, v, qr, kr, ts, block_q=64, block_k=64
                )[0]
                * do
            ).sum(),
            argnums=(0, 1, 2),
        )
    )
    g1 = grad(q, k, v)
    g2 = grad(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), g1, g2):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=name
        )


@pytest.mark.parametrize("d", [72, 96, 256])
def test_non_lane_aligned_head_dims(d):
    """Head dims that are not multiples of the 128 TPU lane width (and the
    wide 256) run correctly — the kernel/Mosaic handles sublane padding
    (reference rounds head_dim up internally, _flex_flash_attn_jit.py)."""
    t, h = 128, 2
    q, k, v = _rand(t, t, h, h, d, seed=d)
    out = flex_flash_attn_func(
        q, k, v, [(0, t)], [(0, t)], [1], block_q=64, block_k=64
    )[0]
    ref = ref_attn_from_ranges(q, k, v, [(0, t)], [(0, t)], [1])[0]
    assert_close(out, ref, atol=3e-5, rtol=3e-5, msg=f"d={d}")
