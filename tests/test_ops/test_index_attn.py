"""Index-attention / sparse-load modes + auto range merge vs the oracle
(reference flex_flash_attn.py:79-178, :1110-1123 sparse options)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from magiattention_tpu.ops import (
    flex_flash_attn_func,
    index_attn_func,
    merge_ranges,
    sparse_load_attn_func,
)
from magiattention_tpu.testing import assert_close, ref_attn


def test_merge_ranges_dedup_and_union():
    q = [(0, 128), (0, 128), (0, 128), (128, 256), (0, 128)]
    k = [(0, 64), (0, 64), (64, 128), (0, 256), (32, 80)]
    t = [0, 0, 0, 1, 1]
    qm, km, tm = merge_ranges(np.array(q), np.array(k), np.array(t))
    rows = sorted(zip(qm[:, 0], qm[:, 1], km[:, 0], km[:, 1], tm))
    # FULL slices with equal q ranges union their k ranges; the causal
    # slices are only deduplicated, never geometry-merged
    assert (0, 128, 0, 128, 0) in [tuple(int(x) for x in r) for r in rows]
    assert len(rows) == 3


def test_auto_range_merge_reduces_entries(monkeypatch):
    """With MAGI_ATTENTION_AUTO_RANGE_MERGE the kernel plan for an
    overlapping-FULL-range mask shrinks and stays numerically identical to
    the canonical mask."""
    from magiattention_tpu.ops.block_meta import build_block_meta

    total = 512
    # 4 overlapping FULL slices covering (0,512)x(0,512)
    q = np.array([[0, 512]] * 4)
    k = np.array([[0, 200], [100, 300], [300, 512], [200, 330]])
    t = np.array([0, 0, 0, 0])
    qm, km, tm = merge_ranges(q, k, t)
    assert qm.shape[0] == 1 and tuple(km[0]) == (0, 512)

    raw = build_block_meta(q, k, t, total, total, block_q=64, block_k=64)
    merged = build_block_meta(qm, km, tm, total, total, block_q=64, block_k=64)
    assert merged.num_fwd_entries < raw.num_fwd_entries

    rng = np.random.default_rng(0)
    qq = jnp.asarray(rng.standard_normal((total, 2, 32)), jnp.float32)
    kk = jnp.asarray(rng.standard_normal((total, 2, 32)), jnp.float32)
    vv = jnp.asarray(rng.standard_normal((total, 2, 32)), jnp.float32)
    monkeypatch.setenv("MAGI_ATTENTION_AUTO_RANGE_MERGE", "1")
    out, _ = flex_flash_attn_func(qq, kk, vv, q, k, t, block_q=64, block_k=64)
    ref_out, _, _ = ref_attn(qq, kk, vv, np.ones((total, total), bool))
    assert_close(out, ref_out, atol=2e-5, rtol=2e-5, msg="merged full")


@pytest.mark.parametrize("causal", [False, True])
def test_index_attn_matches_oracle(causal):
    """Per-q-block top-k KV-block selection (NSA-style index attention)."""
    total, bq, bk, topk = 512, 64, 64, 3
    hq, hk, d = 2, 2, 32
    nq, nk = total // bq, total // bk
    rng = np.random.default_rng(3)
    idx = np.full((nq, topk), -1, np.int64)
    for i in range(nq):
        lim = i + 1 if causal else nk  # keep selections near/below diagonal
        sel = rng.choice(lim, size=min(topk, lim), replace=False)
        idx[i, : len(sel)] = sel
    q = jnp.asarray(rng.standard_normal((total, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((total, hk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((total, hk, d)), jnp.float32)
    out, lse = index_attn_func(
        q, k, v, idx, causal=causal, block_q=bq, block_k=bk
    )
    mask = np.zeros((total, total), bool)
    for i in range(nq):
        for j in idx[i][idx[i] >= 0]:
            mask[i * bq : (i + 1) * bq, j * bk : (j + 1) * bk] = True
    if causal:
        mask &= np.tril(np.ones((total, total), bool))
    ref_out, ref_lse, _ = ref_attn(q, k, v, mask)
    assert_close(out, ref_out, atol=2e-5, rtol=2e-5, msg=f"idx c={causal}")
    finite = ~np.isneginf(np.asarray(ref_lse))
    assert_close(
        np.asarray(lse)[finite], np.asarray(ref_lse)[finite],
        atol=2e-5, rtol=2e-5,
    )


@pytest.mark.parametrize("causal", [False, True])
def test_sparse_load_matches_oracle(causal):
    """Selected global k ranges gathered to a compact buffer; the mask is
    evaluated against GLOBAL positions through run translation."""
    total = 512
    hq, hk, d = 2, 2, 32
    sel = [(0, 96), (160, 288), (384, 512)]
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.standard_normal((total, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((total, hk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((total, hk, d)), jnp.float32)
    out, lse = sparse_load_attn_func(
        q, k, v, sel, causal=causal, block_q=64, block_k=64
    )
    mask = np.zeros((total, total), bool)
    for a, b in sel:
        mask[:, a:b] = True
    if causal:
        mask &= np.tril(np.ones((total, total), bool))
    ref_out, ref_lse, _ = ref_attn(q, k, v, mask)
    assert_close(out, ref_out, atol=2e-5, rtol=2e-5, msg=f"sl c={causal}")
    finite = ~np.isneginf(np.asarray(ref_lse))
    assert_close(
        np.asarray(lse)[finite], np.asarray(ref_lse)[finite],
        atol=2e-5, rtol=2e-5,
    )

    # grads flow through the gather + compact-buffer attention
    do = jnp.asarray(rng.standard_normal((total, hq, d)), jnp.float32)
    g = jax.grad(
        lambda k: (
            sparse_load_attn_func(
                q, k, v, sel, causal=causal, block_q=64, block_k=64
            )[0]
            * do
        ).sum()
    )(k)
    gr = jax.grad(lambda k: (ref_attn(q, k, v, mask)[0] * do).sum())(k)
    assert_close(g, gr, atol=1e-4, rtol=1e-4, msg=f"sl dk c={causal}")
