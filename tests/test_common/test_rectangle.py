"""AttnRectangle cut operations vs dense-mask brute force."""

import numpy as np
import pytest

from magiattention_tpu.common import AttnMaskType, AttnRange
from magiattention_tpu.common.mask import slice_mask
from magiattention_tpu.common.rectangle import AttnRectangle, AttnRectangles

TYPES = list(AttnMaskType)
SPAN = 48


def _dense(rect: AttnRectangle) -> np.ndarray:
    return slice_mask(
        rect.q_range.start,
        rect.q_range.end,
        rect.k_range.start,
        rect.k_range.end,
        rect.mask_type,
        SPAN,
        SPAN,
    )


def _dense_list(rects) -> np.ndarray:
    m = np.zeros((SPAN, SPAN), bool)
    for r in rects:
        m |= _dense(r)
    return m


def _rand_rect(rng, mt):
    qs = int(rng.integers(0, SPAN - 2))
    qe = int(rng.integers(qs + 1, SPAN))
    ks = int(rng.integers(0, SPAN - 2))
    ke = int(rng.integers(ks + 1, SPAN))
    return AttnRectangle(AttnRange(qs, qe), AttnRange(ks, ke), mt)


@pytest.mark.parametrize("mt", TYPES)
@pytest.mark.parametrize("seed", range(6))
def test_cut_q_exact(mt, seed):
    rng = np.random.default_rng(seed)
    rect = _rand_rect(rng, mt)
    pos = int(rng.integers(0, SPAN))
    top, bottom = rect.cut_q(pos)
    m = np.zeros((SPAN, SPAN), bool)
    for piece, rows in ((top, slice(0, pos)), (bottom, slice(pos, SPAN))):
        if piece is None:
            continue
        pm = _dense(piece)
        # piece must stay within its row half
        outside = pm.copy()
        outside[rows] = False
        assert not outside.any()
        m |= pm
    np.testing.assert_array_equal(m, _dense(rect))
    # areas partition
    assert (top.area if top else 0) + (bottom.area if bottom else 0) == rect.area


@pytest.mark.parametrize("mt", TYPES)
@pytest.mark.parametrize("seed", range(6))
def test_cut_k_exact(mt, seed):
    rng = np.random.default_rng(100 + seed)
    rect = _rand_rect(rng, mt)
    pos = int(rng.integers(0, SPAN))
    left, right = rect.cut_k_multi(pos)
    ml = _dense_list(left)
    mr = _dense_list(right)
    assert not ml[:, pos:].any(), "left pieces leak right of the cut"
    assert not mr[:, :pos].any(), "right pieces leak left of the cut"
    np.testing.assert_array_equal(ml | mr, _dense(rect))
    assert not (ml & mr).any()


def test_rectangles_aggregate():
    rects = AttnRectangles.from_ranges(
        [(0, 16), (16, 32)], [(0, 16), (0, 32)],
        [AttnMaskType.CAUSAL, AttnMaskType.CAUSAL],
    )
    total = rects.area
    top, bottom = rects.cut_q(16)
    assert top.area + bottom.area == total
    left, right = rects.cut_k(8)
    assert left.area + right.area == total


@pytest.mark.parametrize("mt", TYPES)
@pytest.mark.parametrize("seed", range(8))
def test_area_left_of_k_closed_form(mt, seed):
    """slice_area_left_of_k (closed form, the dynamic solver's probe) vs
    dense-mask popcount, every cut position."""
    rng = np.random.default_rng(100 + seed)
    rect = _rand_rect(rng, mt)
    dense = _dense(rect)
    rr = AttnRectangles()
    rr.append(rect)
    for pos in range(-1, SPAN + 2):
        expect = int(dense[:, : max(pos, 0)].sum())
        assert rr.area_left_of_k(pos) == expect, (rect, pos)


@pytest.mark.parametrize("seed", range(4))
def test_area_left_of_q_vs_dense(seed):
    """Disjoint q bands (the solver's precondition — mask slices cover
    disjoint plane regions), so the dense union popcount equals the
    per-rect area sum."""
    rng = np.random.default_rng(200 + seed)
    rects = AttnRectangles()
    band = SPAN // len(TYPES)
    for j, mt in enumerate(TYPES):
        qs = j * band + int(rng.integers(0, band // 2))
        qe = int(rng.integers(qs + 1, (j + 1) * band))
        ks = int(rng.integers(0, SPAN - 2))
        ke = int(rng.integers(ks + 1, SPAN))
        rects.append(
            AttnRectangle(AttnRange(qs, qe), AttnRange(ks, ke), mt)
        )
    dense = _dense_list(rects)
    for pos in range(0, SPAN + 1, 5):
        assert rects.area_left_of_q(pos) == int(dense[:pos].sum())
