"""Tests for AttnRange / AttnRanges (model: reference tests/test_common)."""

import numpy as np
import pytest

from magiattention_tpu.common import (
    AttnRange,
    AttnRanges,
    RangeError,
)


class TestAttnRange:
    def test_basic(self):
        r = AttnRange(2, 10)
        assert r.start == 2 and r.end == 10 and r.seqlen == 8 and len(r) == 8
        assert r.to_naive_range() == (2, 10)
        assert AttnRange.from_range((2, 10)) == r
        assert r.clone() == r and r.clone() is not r

    def test_invalid(self):
        with pytest.raises(RangeError):
            AttnRange(5, 3)
        with pytest.raises(RangeError):
            AttnRange(-1, 3)
        r = AttnRange(2, 10)
        with pytest.raises(RangeError):
            r.start = 11
        with pytest.raises(RangeError):
            r.end = 1

    def test_offset_truncate(self):
        r = AttnRange(2, 10)
        assert r.offset(5) == AttnRange(7, 15)
        assert r.truncate(4, 8) == AttnRange(4, 8)
        assert r.truncate(0, 100) == r
        assert r.truncate(20, 30).is_empty()

    def test_set_ops(self):
        a, b = AttnRange(2, 10), AttnRange(5, 15)
        assert a.intersect(b) == AttnRange(5, 10)
        assert a.intersect_size(b) == 5
        assert a.union(b) == [AttnRange(2, 15)]
        assert a.union_size(b) == 13
        c = AttnRange(20, 25)
        assert a.intersect(c).is_empty()
        assert a.union(c) == [a, c]
        assert a.diff_by(b) == [AttnRange(2, 5)]
        assert b.diff_by(a) == [AttnRange(10, 15)]
        assert a.diff_by(AttnRange(4, 6)) == [AttnRange(2, 4), AttnRange(6, 10)]
        assert a.diff_by(AttnRange(0, 100)) == []

    def test_predicates(self):
        a = AttnRange(2, 10)
        assert AttnRange(3, 5).is_subrange_of(a)
        assert not AttnRange(3, 11).is_subrange_of(a)
        assert a.is_overlap_with(AttnRange(9, 12))
        assert not a.is_overlap_with(AttnRange(10, 12))
        assert AttnRange(4, 4).is_empty()
        assert a.is_valid_close(0, 10)
        assert not a.is_valid_close(3, 10)


class TestAttnRanges:
    def test_construction(self):
        rs = AttnRanges.from_ranges([(0, 5), (10, 20)])
        assert len(rs) == 2 and rs.total_seqlen == 15
        assert rs.to_naive_ranges() == [(0, 5), (10, 20)]
        t = rs.to_tensor()
        assert t.shape == (2, 2) and t.dtype == np.int32

    def test_cu_seqlens_roundtrip(self):
        cu = [0, 4, 4, 10, 16]
        rs = AttnRanges.from_cu_seqlens(cu, 16)
        assert rs.to_cu_seqlens(16) == cu
        assert rs.is_cu_seqlens(16)
        assert not AttnRanges.from_ranges([(0, 4), (5, 10)]).is_cu_seqlens(10)

    def test_sort_merge(self):
        rs = AttnRanges.from_ranges([(10, 20), (0, 5), (4, 12), (30, 31)])
        assert not rs.is_sorted()
        assert rs.sort().is_sorted()
        merged = rs.merge()
        assert merged.to_naive_ranges() == [(0, 20), (30, 31)]
        assert merged.is_merged()
        # adjacent ranges coalesce
        assert AttnRanges.from_ranges([(0, 5), (5, 9)]).merge().to_naive_ranges() == [
            (0, 9)
        ]

    def test_merge_with_split_alignment(self):
        rs = AttnRanges.from_ranges([(3, 10), (21, 30)])
        m = rs.merge_with_split_alignment(8)
        # aligned outward rounding: [3,10) → [0,16); [21,30) → [16,32); they touch
        assert m.to_naive_ranges() == [(0, 32)]

    def test_chunk(self):
        rs = AttnRanges.from_ranges([(0, 10), (20, 27)])
        chunks = rs.chunk(6)
        # 17 tokens → chunks of 6, 6, 5
        sizes = [c.total_seqlen for c in chunks]
        assert sizes == [6, 6, 5]
        assert chunks[0].to_naive_ranges() == [(0, 6)]
        assert chunks[1].to_naive_ranges() == [(6, 10), (20, 22)]
        assert chunks[2].to_naive_ranges() == [(22, 27)]
        with pytest.raises(ValueError):
            AttnRanges.from_ranges([(0, 5), (3, 8)]).chunk(4)

    def test_find_hole_ranges(self):
        # example from the reference docstring
        a = AttnRanges.from_ranges([(0, 10), (15, 20), (20, 30)])
        b = AttnRanges.from_ranges([(5, 10), (25, 30)])
        assert a.find_hole_ranges(b).to_naive_ranges() == [(0, 5), (15, 25)]
        # no overlap → a (merged) unchanged
        c = AttnRanges.from_ranges([(100, 110)])
        assert a.find_hole_ranges(c).to_naive_ranges() == [(0, 10), (15, 30)]
        # full cover → empty
        d = AttnRanges.from_ranges([(0, 30)])
        assert a.find_hole_ranges(d).is_empty()

    def test_find_overlap_ranges(self):
        a = AttnRanges.from_ranges([(0, 10), (15, 20), (25, 30)])
        b = AttnRanges.from_ranges([(5, 10), (18, 30)])
        assert a.find_overlap_ranges(b).to_naive_ranges() == [
            (5, 10),
            (18, 20),
            (25, 30),
        ]

    def test_make_ranges_local(self):
        host = AttnRanges.from_ranges([(0, 4), (10, 14), (20, 28)])
        # global [11,13) lives at local 4 + 1 = 5
        local = host.make_ranges_local(AttnRanges.from_ranges([(11, 13), (20, 24)]))
        assert local.to_naive_ranges() == [(5, 7), (8, 12)]
        lr, target = host.make_range_local(AttnRange(2, 4))
        assert lr == AttnRange(2, 4) and target == AttnRange(0, 4)
        with pytest.raises(ValueError):
            host.make_range_local(AttnRange(3, 11))

    def test_size_metrics(self):
        a = AttnRanges.from_ranges([(0, 10), (5, 15)])
        assert a.total_seqlen == 20
        assert a.union_size() == 15
        assert a.intersect_size() == 5
        b = AttnRanges.from_ranges([(8, 20)])
        assert a.intersect_size_with(b) == 7
        assert a.union_size_with(b) == 20
        assert a.max_seqlen == 10
        assert a.start == 0 and a.end == 15
        assert a.points == [0, 5, 10, 15]

    def test_non_overlap(self):
        assert AttnRanges.from_ranges([(0, 5), (5, 10)]).is_non_overlap()
        assert not AttnRanges.from_ranges([(0, 6), (5, 10)]).is_non_overlap()
        assert AttnRanges().is_non_overlap()

    def test_truncate(self):
        rs = AttnRanges.from_ranges([(0, 10), (20, 30)])
        assert rs.truncate(5, 25).to_naive_ranges() == [(5, 10), (20, 25)]
