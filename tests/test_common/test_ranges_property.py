"""Randomized property tests for the range set-algebra against an
integer-set oracle (the coverage depth of reference
tests/test_common/test_attn_ranges.py, 1045 LoC, as properties rather
than enumerated cases)."""

import numpy as np
import pytest

from magiattention_tpu.common.range import AttnRange
from magiattention_tpu.common.ranges import AttnRanges


def _rand_ranges(rng, n, hi, allow_overlap=True):
    rs = AttnRanges()
    for _ in range(n):
        a = int(rng.integers(0, hi - 1))
        b = int(rng.integers(a + 1, hi + 1))
        rs.append(AttnRange(a, b))
    if not allow_overlap:
        rs = rs.merge()
    return rs


def _as_set(rs: AttnRanges) -> set:
    out = set()
    for r in rs:
        out |= set(range(r.start, r.end))
    return out


@pytest.mark.parametrize("seed", range(20))
def test_merge_equals_set_and_is_canonical(seed):
    rng = np.random.default_rng(seed)
    rs = _rand_ranges(rng, int(rng.integers(1, 10)), 200)
    m = rs.merge()
    assert _as_set(m) == _as_set(rs)
    assert m.is_sorted() and m.is_merged() and m.is_non_overlap()
    # merged ranges are maximal: no two adjacent ranges touch
    naive = m.to_naive_ranges()
    for (a0, a1), (b0, b1) in zip(naive, naive[1:]):
        assert a1 < b0


@pytest.mark.parametrize("seed", range(20))
def test_chunk_partitions_exactly(seed):
    rng = np.random.default_rng(seed)
    rs = _rand_ranges(rng, int(rng.integers(1, 8)), 300, allow_overlap=False)
    if rs.total_seqlen == 0:
        return
    chunk = int(rng.integers(1, rs.total_seqlen + 1))
    chunks = rs.chunk(chunk, check=False)
    # chunks tile the token set exactly, in order, each <= chunk tokens
    got = []
    for c in chunks:
        n = sum(r.seqlen for r in c)
        assert 0 < n <= chunk
        for r in c:
            got.extend(range(r.start, r.end))
    want = sorted(_as_set(rs))
    assert got == want


@pytest.mark.parametrize("seed", range(20))
def test_find_hole_ranges_is_set_difference(seed):
    rng = np.random.default_rng(seed)
    need = _rand_ranges(rng, int(rng.integers(1, 8)), 200, allow_overlap=False)
    have = _rand_ranges(rng, int(rng.integers(1, 8)), 200, allow_overlap=False)
    holes = need.find_hole_ranges(have)
    assert _as_set(holes) == (_as_set(need) - _as_set(have))
    assert holes.is_non_overlap()


@pytest.mark.parametrize("seed", range(20))
def test_find_overlap_ranges_is_set_intersection(seed):
    rng = np.random.default_rng(seed)
    a = _rand_ranges(rng, int(rng.integers(1, 8)), 200, allow_overlap=False)
    b = _rand_ranges(rng, int(rng.integers(1, 8)), 200, allow_overlap=False)
    ov = a.find_overlap_ranges(b)
    assert _as_set(ov) == (_as_set(a) & _as_set(b))


@pytest.mark.parametrize("seed", range(20))
def test_make_ranges_local_roundtrip(seed):
    """Local coordinates: position p global -> index of p within the host
    token list. Translating sub-ranges of the host set must preserve the
    token multiset under the host's global->local order isomorphism."""
    rng = np.random.default_rng(seed)
    host = _rand_ranges(rng, int(rng.integers(1, 8)), 200, allow_overlap=False)
    host = host.merge()
    toks = sorted(_as_set(host))
    if not toks:
        return
    # random sub-selection of host tokens, as ranges
    mask = rng.random(len(toks)) < 0.5
    sel_tokens = [t for t, m in zip(toks, mask) if m]
    sub = AttnRanges()
    i = 0
    while i < len(sel_tokens):
        j = i + 1
        while j < len(sel_tokens) and sel_tokens[j] == sel_tokens[j - 1] + 1:
            j += 1
        sub.append(AttnRange(sel_tokens[i], sel_tokens[j - 1] + 1))
        i = j
    if len(sub) == 0:
        return
    local = host.make_ranges_local(sub)
    glob_to_loc = {t: i for i, t in enumerate(toks)}
    want = sorted(glob_to_loc[t] for t in sel_tokens)
    assert sorted(_as_set(local)) == want


@pytest.mark.parametrize("seed", range(10))
def test_merge_with_split_alignment_properties(seed):
    rng = np.random.default_rng(seed)
    rs = _rand_ranges(rng, int(rng.integers(1, 8)), 256, allow_overlap=False)
    align = int(rng.choice([2, 4, 16, 32]))
    m = rs.merge_with_split_alignment(align)
    # outward rounding: an aligned, merged SUPERSET of the token set whose
    # expansion stays within the rounding slack (reference split_alignment
    # machinery, dist_attn_solver.py:107-179)
    assert _as_set(m) >= _as_set(rs)
    assert m.is_sorted() and m.is_non_overlap()
    for a, b in m.to_naive_ranges():
        assert a % align == 0 and b % align == 0, (a, b, align)
    # each aligned range only covers tokens within `align-1` of a real one
    covered = _as_set(rs)
    for t in _as_set(m) - covered:
        lo = t // align * align
        assert any(lo <= u < lo + align for u in covered), t
