"""Protocol conformance: concrete implementations satisfy the structural
contracts (reference tests/test_common/test_protocol_conformance.py)."""

from magiattention_tpu.common.protocols import (
    EntryEmitter,
    RangeProtocol,
    RangesProtocol,
    RectangleProtocol,
    RectanglesProtocol,
    SliceAreaFn,
)


def test_range_conformance():
    from magiattention_tpu.common.range import AttnRange

    assert isinstance(AttnRange(0, 4), RangeProtocol)


def test_ranges_conformance():
    from magiattention_tpu.common.ranges import AttnRanges

    assert isinstance(AttnRanges.from_ranges([(0, 4)]), RangesProtocol)


def test_rectangle_conformance():
    from magiattention_tpu.common.range import AttnRange
    from magiattention_tpu.common.rectangle import (
        AttnRectangle,
        AttnRectangles,
    )

    r = AttnRectangle(AttnRange(0, 4), AttnRange(0, 4))
    assert isinstance(r, RectangleProtocol)
    rs = AttnRectangles.from_ranges([(0, 4)], [(0, 4)], [0])
    assert isinstance(rs, RectanglesProtocol)


def test_entry_emitter_conformance():
    """Both accelerator backends satisfy the callable contracts."""
    from magiattention_tpu.csrc import (
        emit_entries_native,
        slice_area_runs_native,
    )
    from magiattention_tpu.ops.block_meta import _emit_entries

    assert isinstance(_emit_entries, EntryEmitter)
    assert isinstance(emit_entries_native, EntryEmitter)
    assert isinstance(slice_area_runs_native, SliceAreaFn)
