"""Mask materialization + area formulas vs brute force."""

import numpy as np
import pytest

from magiattention_tpu.common import (
    AttnMaskType,
    AttnRanges,
    make_attn_mask_from_ranges,
    slice_area,
    slice_mask,
)

MASK_TYPES = [
    AttnMaskType.FULL,
    AttnMaskType.CAUSAL,
    AttnMaskType.INVCAUSAL,
    AttnMaskType.BICAUSAL,
]


def test_mask_type_int_abi():
    assert AttnMaskType.FULL.to_int_type() == 0
    assert AttnMaskType.CAUSAL.to_int_type() == 1
    assert AttnMaskType.INVCAUSAL.to_int_type() == 2
    assert AttnMaskType.BICAUSAL.to_int_type() == 3
    assert AttnMaskType.from_int_type(3) is AttnMaskType.BICAUSAL
    assert AttnMaskType.BICAUSAL.is_causal_bound
    assert AttnMaskType.BICAUSAL.is_inv_causal_bound
    assert not AttnMaskType.FULL.is_causal_bound


def test_causal_semantics_docstring_examples():
    # reference flex_flash_attn.py docstring examples, sq=5 sk=2
    m = slice_mask(0, 5, 0, 2, AttnMaskType.CAUSAL, 5, 2)
    expected = np.array(
        [[0, 0], [0, 0], [0, 0], [1, 0], [1, 1]], dtype=bool
    )
    np.testing.assert_array_equal(m, expected)
    # sq=2 sk=5
    m = slice_mask(0, 2, 0, 5, AttnMaskType.CAUSAL, 2, 5)
    expected = np.array([[1, 1, 1, 1, 0], [1, 1, 1, 1, 1]], dtype=bool)
    np.testing.assert_array_equal(m, expected)


def test_invcausal_semantics_docstring_examples():
    m = slice_mask(0, 5, 0, 2, AttnMaskType.INVCAUSAL, 5, 2)
    expected = np.array(
        [[1, 1], [0, 1], [0, 0], [0, 0], [0, 0]], dtype=bool
    )
    np.testing.assert_array_equal(m, expected)
    m = slice_mask(0, 2, 0, 5, AttnMaskType.INVCAUSAL, 2, 5)
    expected = np.array([[1, 1, 1, 1, 1], [0, 1, 1, 1, 1]], dtype=bool)
    np.testing.assert_array_equal(m, expected)


def test_bicausal_semantics_docstring_examples():
    m = slice_mask(0, 5, 0, 2, AttnMaskType.BICAUSAL, 5, 2)
    assert not m.any()
    m = slice_mask(0, 2, 0, 5, AttnMaskType.BICAUSAL, 2, 5)
    expected = np.array([[1, 1, 1, 1, 0], [0, 1, 1, 1, 1]], dtype=bool)
    np.testing.assert_array_equal(m, expected)
    m = slice_mask(0, 5, 0, 5, AttnMaskType.BICAUSAL, 5, 5)
    np.testing.assert_array_equal(m, np.eye(5, dtype=bool))


@pytest.mark.parametrize("mt", MASK_TYPES)
@pytest.mark.parametrize("sq,sk", [(1, 1), (3, 7), (7, 3), (5, 5), (8, 1), (1, 8)])
def test_area_matches_mask_popcount(mt, sq, sk):
    qs, ks = 2, 3  # offsets should not matter
    m = slice_mask(qs, qs + sq, ks, ks + sk, mt, qs + sq + 1, ks + sk + 2)
    assert slice_area(qs, qs + sq, ks, ks + sk, mt) == int(m.sum())


def test_make_attn_mask_union():
    q_ranges = AttnRanges.from_ranges([(0, 4), (4, 8)])
    k_ranges = AttnRanges.from_ranges([(0, 4), (0, 8)])
    mask = make_attn_mask_from_ranges(
        q_ranges, k_ranges, [AttnMaskType.FULL, AttnMaskType.CAUSAL], 8, 8
    )
    # rows 0-3 attend keys 0-3 fully
    assert mask[:4, :4].all() and not mask[:4, 4:].any()
    # rows 4-7: causal bottom-right over k [0,8)
    for i, row in enumerate(mask[4:]):
        assert row.sum() == 5 + i
