"""Mask materialization + area formulas vs brute force."""

import numpy as np
import pytest

from magiattention_tpu.common import (
    AttnMaskType,
    AttnRanges,
    make_attn_mask_from_ranges,
    slice_area,
    slice_mask,
)

MASK_TYPES = [
    AttnMaskType.FULL,
    AttnMaskType.CAUSAL,
    AttnMaskType.INVCAUSAL,
    AttnMaskType.BICAUSAL,
]


def test_mask_type_int_abi():
    assert AttnMaskType.FULL.to_int_type() == 0
    assert AttnMaskType.CAUSAL.to_int_type() == 1
    assert AttnMaskType.INVCAUSAL.to_int_type() == 2
    assert AttnMaskType.BICAUSAL.to_int_type() == 3
    assert AttnMaskType.from_int_type(3) is AttnMaskType.BICAUSAL
    assert AttnMaskType.BICAUSAL.is_causal_bound
    assert AttnMaskType.BICAUSAL.is_inv_causal_bound
    assert not AttnMaskType.FULL.is_causal_bound


def test_causal_semantics_docstring_examples():
    # reference flex_flash_attn.py docstring examples, sq=5 sk=2
    m = slice_mask(0, 5, 0, 2, AttnMaskType.CAUSAL, 5, 2)
    expected = np.array(
        [[0, 0], [0, 0], [0, 0], [1, 0], [1, 1]], dtype=bool
    )
    np.testing.assert_array_equal(m, expected)
    # sq=2 sk=5
    m = slice_mask(0, 2, 0, 5, AttnMaskType.CAUSAL, 2, 5)
    expected = np.array([[1, 1, 1, 1, 0], [1, 1, 1, 1, 1]], dtype=bool)
    np.testing.assert_array_equal(m, expected)


def test_invcausal_semantics_docstring_examples():
    m = slice_mask(0, 5, 0, 2, AttnMaskType.INVCAUSAL, 5, 2)
    expected = np.array(
        [[1, 1], [0, 1], [0, 0], [0, 0], [0, 0]], dtype=bool
    )
    np.testing.assert_array_equal(m, expected)
    m = slice_mask(0, 2, 0, 5, AttnMaskType.INVCAUSAL, 2, 5)
    expected = np.array([[1, 1, 1, 1, 1], [0, 1, 1, 1, 1]], dtype=bool)
    np.testing.assert_array_equal(m, expected)


def test_bicausal_semantics_docstring_examples():
    m = slice_mask(0, 5, 0, 2, AttnMaskType.BICAUSAL, 5, 2)
    assert not m.any()
    m = slice_mask(0, 2, 0, 5, AttnMaskType.BICAUSAL, 2, 5)
    expected = np.array([[1, 1, 1, 1, 0], [0, 1, 1, 1, 1]], dtype=bool)
    np.testing.assert_array_equal(m, expected)
    m = slice_mask(0, 5, 0, 5, AttnMaskType.BICAUSAL, 5, 5)
    np.testing.assert_array_equal(m, np.eye(5, dtype=bool))


@pytest.mark.parametrize("mt", MASK_TYPES)
@pytest.mark.parametrize("sq,sk", [(1, 1), (3, 7), (7, 3), (5, 5), (8, 1), (1, 8)])
def test_area_matches_mask_popcount(mt, sq, sk):
    qs, ks = 2, 3  # offsets should not matter
    m = slice_mask(qs, qs + sq, ks, ks + sk, mt, qs + sq + 1, ks + sk + 2)
    assert slice_area(qs, qs + sq, ks, ks + sk, mt) == int(m.sum())


def test_make_attn_mask_union():
    q_ranges = AttnRanges.from_ranges([(0, 4), (4, 8)])
    k_ranges = AttnRanges.from_ranges([(0, 4), (0, 8)])
    mask = make_attn_mask_from_ranges(
        q_ranges, k_ranges, [AttnMaskType.FULL, AttnMaskType.CAUSAL], 8, 8
    )
    # rows 0-3 attend keys 0-3 fully
    assert mask[:4, :4].all() and not mask[:4, 4:].any()
    # rows 4-7: causal bottom-right over k [0,8)
    for i, row in enumerate(mask[4:]):
        assert row.sum() == 5 + i


def test_online_oracle_matches_dense():
    import jax.numpy as jnp
    from magiattention_tpu.testing import ref_attn, ref_attn_online

    rng = np.random.default_rng(11)
    tq = tk = 160
    q = jnp.asarray(rng.standard_normal((tq, 4, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((tk, 2, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((tk, 2, 32)), jnp.float32)
    mask = make_attn_mask_from_ranges(
        AttnRanges.from_ranges([(0, 100), (100, 160)]),
        AttnRanges.from_ranges([(0, 100), (0, 160)]),
        [AttnMaskType.CAUSAL, AttnMaskType.CAUSAL], tq, tk,
    )
    out_d, lse_d, _ = ref_attn(q, k, v, mask)
    out_o, lse_o = ref_attn_online(q, k, v, mask, block=48)
    np.testing.assert_allclose(np.asarray(out_o), np.asarray(out_d), atol=2e-6, rtol=2e-6)
    finite = ~np.isneginf(np.asarray(lse_d))
    np.testing.assert_allclose(
        np.asarray(lse_o)[finite], np.asarray(lse_d)[finite], atol=2e-6, rtol=2e-6)


def test_gt_dispatcher_matches_meta():
    from magiattention_tpu.meta import make_dispatch_meta_from_qk_ranges
    from magiattention_tpu.testing import GroundTruthDispatcher

    q = AttnRanges.from_ranges([(0, 128)])
    mq, _, _ = make_dispatch_meta_from_qk_ranges(q, q, [1], 128, 128, chunk_size=16, cp_size=4)
    gt = GroundTruthDispatcher(mq)
    x = np.arange(128)
    np.testing.assert_array_equal(gt.dispatch(x), x[mq.perm_idx])
    np.testing.assert_array_equal(gt.undispatch(gt.dispatch(x)), x)
    for r in range(4):
        np.testing.assert_array_equal(gt.shard(x, r), x[mq.position_ids(r)])


def test_flag_comb_generator():
    from magiattention_tpu.testing import FlagCombGenerator

    space = {"a": [1, 2, 3], "b": [True, False]}
    seq = list(FlagCombGenerator(space, mode="sequential"))
    assert len(seq) == 6
    heur = list(FlagCombGenerator(space, mode="heuristic"))
    assert len(heur) == 1 + 2 + 1  # base + |a|-1 + |b|-1
    legal = lambda c: not (c["a"] == 3 and c["b"])
    assert all(legal(c) for c in FlagCombGenerator(space, legal, mode="sequential"))
