"""Disjoint-coverage sanity check vs dense-mask brute force."""

import numpy as np
import pytest

from magiattention_tpu.common import AttnMaskType
from magiattention_tpu.common.mask import slice_mask
from magiattention_tpu.common.sanity import check_slices_non_overlapping

SPAN = 64


def _brute_overlap(qr, kr, ts):
    acc = np.zeros((SPAN, SPAN), np.int32)
    for (qs, qe), (ks, ke), t in zip(qr, kr, ts):
        acc += slice_mask(qs, qe, ks, ke, t, SPAN, SPAN).astype(np.int32)
    return (acc > 1).any()


@pytest.mark.parametrize("seed", range(30))
def test_matches_brute_force(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(2, 5))
    qr, kr, ts = [], [], []
    for _ in range(n):
        qs = int(rng.integers(0, SPAN - 2)); qe = int(rng.integers(qs + 1, SPAN))
        ks = int(rng.integers(0, SPAN - 2)); ke = int(rng.integers(ks + 1, SPAN))
        qr.append((qs, qe)); kr.append((ks, ke)); ts.append(int(rng.integers(0, 4)))
    expect_overlap = _brute_overlap(qr, kr, ts)
    if expect_overlap:
        with pytest.raises(ValueError):
            check_slices_non_overlapping(qr, kr, ts)
    else:
        check_slices_non_overlapping(qr, kr, ts)


def test_known_cases():
    # disjoint: causal + inv-causal band above the diagonal
    check_slices_non_overlapping(
        [(0, 64), (16, 48)], [(0, 64), (32, 64)], [1, 2]
    )
    # overlapping: causal covers the full slice's band
    with pytest.raises(ValueError, match="double-count"):
        check_slices_non_overlapping(
            [(0, 64), (16, 48)], [(0, 64), (0, 16)], [1, 0]
        )
