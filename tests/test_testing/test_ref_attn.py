"""Validate the oracle itself (role of reference tests/test_attn/
test_ref_attn.py): the jnp reference is the ground truth for every other
test, so it gets checked against a fully independent fp64 numpy
implementation, its own online variant, analytic identities, and finite
differences.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from magiattention_tpu.common import make_attn_mask_from_ranges
from magiattention_tpu.testing import ref_attn_from_ranges
from magiattention_tpu.testing.ref_attn import ref_attn, ref_attn_online


def _numpy_attn(q, k, v, mask, softcap=0.0, sink=None):
    """Independent fp64 implementation: per-row explicit softmax."""
    tq, hq, d = q.shape
    tk, hk, _ = k.shape
    g = hq // hk
    out = np.zeros((tq, hq, d))
    lse = np.full((tq, hq), -np.inf)
    mx = np.full(hq, -np.inf)
    scale = 1.0 / np.sqrt(d)
    for h in range(hq):
        kh, vh = k[:, h // g], v[:, h // g]
        for i in range(tq):
            sel = mask[i]
            s = (kh[sel] @ q[i, h]) * scale
            if softcap > 0:
                s = softcap * np.tanh(s / softcap)
            if s.size:
                mx[h] = max(mx[h], s.max())
            terms = list(s)
            if sink is not None:
                terms.append(float(sink[h]))
            if not terms:
                continue
            m = max(terms)
            Z = sum(np.exp(t - m) for t in terms)
            lse[i, h] = m + np.log(Z)
            if s.size:
                p = np.exp(s - lse[i, h])
                out[i, h] = p @ vh[sel]
    return out, lse, mx


CASES = [
    dict(hq=2, hk=2, softcap=0.0, sink=False),
    dict(hq=4, hk=2, softcap=0.0, sink=False),
    dict(hq=4, hk=1, softcap=12.0, sink=False),
    dict(hq=2, hk=2, softcap=0.0, sink=True),
    dict(hq=4, hk=2, softcap=8.0, sink=True),
]


@pytest.mark.parametrize("case", CASES, ids=[str(c) for c in CASES])
def test_oracle_vs_independent_fp64(case):
    tq = tk = 48
    d = 16
    rng = np.random.default_rng(7)
    q = rng.standard_normal((tq, case["hq"], d))
    k = rng.standard_normal((tk, case["hk"], d))
    v = rng.standard_normal((tk, case["hk"], d))
    sink = rng.standard_normal(case["hq"]) if case["sink"] else None
    # mixed mask with an uncovered q row region [40, 48)
    qr = [(0, 16), (16, 40), (8, 24)]
    kr = [(0, 32), (16, 48), (32, 48)]
    ts = [1, 2, 0]
    mask = make_attn_mask_from_ranges(qr, kr, ts, tq, tk)

    out, lse, mx = ref_attn(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mask,
        softcap=case["softcap"],
        sink=jnp.asarray(sink) if sink is not None else None,
        compute_dtype=jnp.float64,
    )
    eout, else_, emx = _numpy_attn(
        q, k, v, mask, softcap=case["softcap"], sink=sink
    )
    np.testing.assert_allclose(np.asarray(out), eout, atol=1e-10)
    np.testing.assert_allclose(np.asarray(lse), else_, atol=1e-10)
    np.testing.assert_allclose(np.asarray(mx), emx, atol=1e-10)


def test_offline_vs_online_oracle():
    tq = tk = 96
    hq, hk, d = 4, 2, 32
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((tq, hq, d)), jnp.float64)
    k = jnp.asarray(rng.standard_normal((tk, hk, d)), jnp.float64)
    v = jnp.asarray(rng.standard_normal((tk, hk, d)), jnp.float64)
    mask = make_attn_mask_from_ranges(
        [(0, 48), (48, 96)], [(0, 96), (24, 72)], [1, 3], tq, tk
    )
    o1, l1, _ = ref_attn(q, k, v, mask, compute_dtype=jnp.float64)
    o2, l2 = ref_attn_online(
        q, k, v, mask, block=17, compute_dtype=jnp.float64
    )
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-12)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-12)


def test_sink_rescale_identity():
    """out_sink == out * exp(lse - lse_sink): adding a sink only rescales
    each row by the enlarged softmax denominator."""
    tq = tk = 64
    hq, hk, d = 2, 2, 16
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.standard_normal((tq, hq, d)), jnp.float64)
    k = jnp.asarray(rng.standard_normal((tk, hk, d)), jnp.float64)
    v = jnp.asarray(rng.standard_normal((tk, hk, d)), jnp.float64)
    sink = jnp.asarray(rng.standard_normal(hq), jnp.float64)
    qr, kr, ts = [(0, tq)], [(0, tk)], [1]
    o, l, _ = ref_attn_from_ranges(q, k, v, qr, kr, ts,
                                   compute_dtype=jnp.float64)
    os_, ls, _ = ref_attn_from_ranges(q, k, v, qr, kr, ts, sink=sink,
                                      compute_dtype=jnp.float64)
    np.testing.assert_allclose(
        np.asarray(os_),
        np.asarray(o) * np.exp(np.asarray(l) - np.asarray(ls))[:, :, None],
        atol=1e-12,
    )


def test_oracle_grads_finite_difference():
    tq = tk = 24
    hq, hk, d = 2, 1, 8
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.standard_normal((tq, hq, d)), jnp.float64)
    k = jnp.asarray(rng.standard_normal((tk, hk, d)), jnp.float64)
    v = jnp.asarray(rng.standard_normal((tk, hk, d)), jnp.float64)
    do = jnp.asarray(rng.standard_normal((tq, hq, d)), jnp.float64)
    qr, kr, ts = [(0, tq)], [(0, tk)], [1]

    def f(q, k, v):
        return (
            ref_attn_from_ranges(
                q, k, v, qr, kr, ts, compute_dtype=jnp.float64
            )[0]
            * do
        ).sum()

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    eps = 1e-6
    for name, arg, idx, grad in (
        ("dq", q, 0, g[0]),
        ("dk", k, 1, g[1]),
        ("dv", v, 2, g[2]),
    ):
        probe = np.zeros(arg.shape)
        probe[arg.shape[0] // 2, 0, 3] = 1.0
        args = [q, k, v]
        args_p = list(args)
        args_p[idx] = arg + eps * probe
        args_m = list(args)
        args_m[idx] = arg - eps * probe
        fd = (f(*args_p) - f(*args_m)) / (2 * eps)
        an = float((np.asarray(grad) * probe).sum())
        assert abs(fd - an) < 1e-6 * max(1.0, abs(an)), (name, fd, an)
